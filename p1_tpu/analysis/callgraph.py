"""Whole-package call graph with per-node effect summaries.

The round-13 rules are lexical: each looks at one function body in one
file.  That left two documented residues (docs/LINT.md): a blocking
call ONE helper deep escapes ``blocking-in-async`` entirely, and
``await-state`` cannot see a consensus-state read or write routed
through a method call.  Both matter NOW because ROADMAP item 2 (the
multi-core stage split) names those rules as its guardrails — the
refactor moves code off the loop, and the analyzer must see through
calls to know what is actually running on it.

This module builds the interprocedural layer from the engine's
existing one-parse-per-file trees — no new parses, no imports of the
analyzed code (a lint must not execute its subject):

- **Nodes** are module-level functions and class methods, identified
  as ``"rel::Qual.name"`` (``"node/node.py::Node._dispatch"``).
- **Edges** come from structural call resolution: bare names bind to
  module functions or ``from``-imports; dotted names through imported
  ``p1_tpu`` modules; ``self.helper()`` / ``cls.helper()`` to methods
  of the enclosing class (single-inheritance bases resolvable in the
  package are searched too); ``ClassName(...)`` to ``__init__``; and
  ``self.attr.meth()`` when the class assigns ``self.attr =
  SomeClass(...)`` unambiguously (the one-level attribute-type
  binding that lets the graph follow ``self.store.append``).
  Anything else — higher-order values, externals, attribute chains
  with no binding — stays an unresolved dotted name: the graph is
  deliberately an UNDER-approximation, precise where it claims edges.
  A callable merely *passed* (``asyncio.to_thread(self._sync_io)``)
  is NOT an edge: that is exactly the house pattern for moving work
  off-loop, and charging it to the caller would flag the fix.
- **Effect summaries** per node: direct blocking-primitive calls
  (``time.sleep``, builtin ``open``, ``os.fsync``/``fdatasync``/
  ``sync``, ``subprocess.*``, and ctypes natives — ``ctypes.CDLL``
  loads plus calls through a module-level CDLL handle), watched
  consensus-state reads/writes (``self.chain``/``ledger``/``store``/
  ``mempool``), await positions, and local set-typed name bindings
  (the ``set-iteration`` rule's one-dataflow-hop upgrade).

``blocking_paths()`` is the fixed point the ``transitive-blocking``
rule rides: blocking-ness propagates up call edges until stable, and
every blocking node remembers one concrete witness chain down to the
primitive so a finding can print the full call path.

Nested ``def``/``lambda`` bodies are excluded from a node's own
effects and calls (they run whenever something CALLS them — usually
off-loop via executors), matching the lexical rules' semantics.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from p1_tpu.analysis.base import (
    dotted_name,
    is_set_expr,
    sort_key,
    walk_no_nested_defs,
)

#: Consensus-state attributes on ``self`` whose cross-await
#: interleavings the await-state/escaped-state rules pin (the same
#: watchlist as rules/awaitstate.py — imported from here so the two
#: layers cannot drift).
WATCHED_STATE = frozenset({"chain", "ledger", "store", "mempool"})

#: Dotted spellings that block the host thread outright.
BLOCKING_DOTTED = frozenset(
    {"time.sleep", "os.fsync", "os.fdatasync", "os.sync"}
)


@dataclass(frozen=True)
class CallSite:
    """One call made by a function's own control flow."""

    dotted: str  #: structural spelling ("self.helper", "store.append")
    target: str | None  #: resolved node qual, or None (unresolved)
    line: int


@dataclass
class FuncNode:
    """One function or method: the call-graph node plus its summary."""

    qual: str  #: "rel::name" or "rel::Class.name"
    rel: str
    name: str  #: qualname within the module ("Node._dispatch")
    line: int
    is_async: bool
    tree: ast.AST  #: the (Async)FunctionDef
    calls: list[CallSite] = field(default_factory=list)
    #: direct blocking primitives: (primitive label, line)
    blocking: list[tuple[str, int]] = field(default_factory=list)
    #: watched self.X reads/writes in own control flow: (attr, pos)
    state_reads: list[tuple[str, tuple[int, int]]] = field(
        default_factory=list
    )
    state_writes: list[tuple[str, tuple[int, int]]] = field(
        default_factory=list
    )
    awaits: list[tuple[int, int]] = field(default_factory=list)
    #: local names every binding of which is structurally a set
    set_locals: frozenset[str] = frozenset()


@dataclass(frozen=True)
class BlockingWitness:
    """Why a node is (transitively) blocking: either a direct
    primitive, or one resolved callee that is."""

    primitive: str  #: the blocking primitive at the chain's end
    line: int  #: line IN THIS NODE (the call that starts the chain)
    via: str | None  #: callee qual for indirect, None for direct


class CallGraph:
    """The package-wide graph.  Build once per analysis run from the
    engine's parsed trees; every interprocedural rule reads it."""

    def __init__(self, trees: dict[str, ast.Module]):
        self.nodes: dict[str, FuncNode] = {}
        #: rel -> local qualname -> node qual (module's own defs)
        self._locals: dict[str, dict[str, str]] = {}
        #: rel -> imported name -> ("module", rel') | ("obj", rel', attr)
        self._imports: dict[str, dict[str, tuple]] = {}
        #: rel -> class name -> {method name -> qual}
        self._classes: dict[str, dict[str, dict[str, str]]] = {}
        #: rel -> class name -> base spellings (Name/Attribute dotted)
        self._bases: dict[str, dict[str, list[str]]] = {}
        #: rel -> class name -> self-attr name -> (rel', class') type
        self._attr_types: dict[str, dict[str, dict[str, tuple[str, str]]]] = {}
        #: rel -> module-level names bound to ctypes.CDLL(...) handles
        self._cdll_handles: dict[str, set[str]] = {}
        #: dotted module path ("node.supervision") -> rel, for resolving
        #: absolute p1_tpu imports without touching the filesystem.
        self._modpaths: dict[str, str] = {}
        for rel in trees:
            mod = rel[:-3].replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            self._modpaths[mod] = rel
        for rel, tree in sorted(trees.items()):
            self._index_module(rel, tree)
        for rel, tree in sorted(trees.items()):
            self._collect_effects(rel, tree)
        self.edges = sum(
            1 for n in self.nodes.values() for c in n.calls if c.target
        )

    # -- module indexing -------------------------------------------------

    def _index_module(self, rel: str, tree: ast.Module) -> None:
        local: dict[str, str] = {}
        classes: dict[str, dict[str, str]] = {}
        bases: dict[str, list[str]] = {}
        imports: dict[str, tuple] = {}
        cdll: set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{rel}::{stmt.name}"
                local[stmt.name] = qual
                self._add_node(qual, rel, stmt.name, stmt)
            elif isinstance(stmt, ast.ClassDef):
                methods: dict[str, str] = {}
                for sub in stmt.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        name = f"{stmt.name}.{sub.name}"
                        qual = f"{rel}::{name}"
                        methods[sub.name] = qual
                        self._add_node(qual, rel, name, sub)
                classes[stmt.name] = methods
                bases[stmt.name] = [
                    d for d in map(dotted_name, stmt.bases) if d
                ]
            elif isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    target = self._resolve_module(stmt, alias.name, rel)
                    if target is None:
                        continue
                    bound = alias.asname or alias.name.split(".")[0]
                    if alias.asname:
                        imports[bound] = ("module", target)
                    else:
                        # ``import p1_tpu.node.x`` binds "p1_tpu"; calls
                        # spell the full dotted path — record it whole.
                        imports[alias.name] = ("module", target)
            elif isinstance(stmt, ast.ImportFrom):
                base = self._from_base(stmt, rel)
                if base is None:
                    continue
                for alias in stmt.names:
                    bound = alias.asname or alias.name
                    sub = f"{base}.{alias.name}" if base else alias.name
                    if sub in self._modpaths:
                        imports[bound] = ("module", self._modpaths[sub])
                    elif base in self._modpaths:
                        imports[bound] = (
                            "obj",
                            self._modpaths[base],
                            alias.name,
                        )
            elif isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and _contains_cdll(
                        stmt.value
                    ):
                        cdll.add(tgt.id)
        self._locals[rel] = local
        self._classes[rel] = classes
        self._bases[rel] = bases
        self._imports[rel] = imports
        self._cdll_handles[rel] = cdll
        self._attr_types[rel] = {
            cname: self._infer_attr_types(rel, tree, cname)
            for cname in classes
        }

    def _add_node(self, qual: str, rel: str, name: str, fn: ast.AST) -> None:
        self.nodes[qual] = FuncNode(
            qual=qual,
            rel=rel,
            name=name,
            line=fn.lineno,
            is_async=isinstance(fn, ast.AsyncFunctionDef),
            tree=fn,
        )

    def _resolve_module(self, stmt, modname: str, rel: str) -> str | None:
        if modname.startswith("p1_tpu.") or modname == "p1_tpu":
            inner = modname[len("p1_tpu.") :] if "." in modname else ""
            return self._modpaths.get(inner)
        return None

    def _from_base(self, stmt: ast.ImportFrom, rel: str) -> str | None:
        """The dotted package-relative base of a ``from X import Y``,
        or None when it points outside the package."""
        if stmt.level == 0:
            mod = stmt.module or ""
            if mod == "p1_tpu":
                return ""
            if mod.startswith("p1_tpu."):
                return mod[len("p1_tpu.") :]
            return None
        # relative: level 1 = this module's package, each extra level up
        parts = rel.split("/")[:-1]  # containing package dirs
        up = stmt.level - 1
        if up > len(parts):
            return None
        parts = parts[: len(parts) - up]
        base = ".".join(parts)
        if stmt.module:
            base = f"{base}.{stmt.module}" if base else stmt.module
        return base

    def _infer_attr_types(
        self, rel: str, tree: ast.Module, cname: str
    ) -> dict[str, tuple[str, str]]:
        """``self.X = SomeClass(...)`` anywhere in the class body gives
        X the type SomeClass — kept only when every assignment that
        NAMES a package class agrees (two different classes drop the
        binding).  Assignments with no class information — a parameter
        passthrough (``self.store = store``), ``None``, an expression
        the classifier can't read — are neutral: the injectable-
        dependency idiom (``self.store = store`` in one branch,
        ``ChainStore(...)`` default in the other) keeps the default's
        type, which is the structural truth tests substitute AROUND,
        not away from.  ``a or SomeClass(...)`` / conditional
        expressions count their class operands."""
        cls_node = next(
            (
                s
                for s in tree.body
                if isinstance(s, ast.ClassDef) and s.name == cname
            ),
            None,
        )
        if cls_node is None:
            return {}
        out: dict[str, tuple[str, str] | None] = {}
        for node in ast.walk(cls_node):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                typ = self._value_class(rel, node.value)
                if typ is None:
                    continue  # neutral: no class information
                prev = out.get(tgt.attr, typ)
                out[tgt.attr] = self._unify_classes(typ, prev)
        return {k: v for k, v in out.items() if v is not None}

    def _ancestors(self, key: tuple[str, str]) -> list[tuple[str, str]]:
        """``key`` plus every package-resolvable base, MRO order."""
        seen: list[tuple[str, str]] = []
        stack = [key]
        while stack:
            cur = stack.pop(0)
            if cur in seen:
                continue
            seen.append(cur)
            crel, cname = cur
            for base in self._bases.get(crel, {}).get(cname, ()):
                bhit = self._class_by_dotted(crel, base)
                if bhit is not None:
                    stack.append(bhit)
        return seen

    def _unify_classes(
        self,
        a: tuple[str, str] | None,
        b: tuple[str, str] | None,
    ) -> tuple[str, str] | None:
        """The nearest common ANCESTOR of two bindings, or None when
        they are unrelated.  Subclass/base pairs unify to the base —
        the round-18 shape: ``self.store`` is a ``SegmentedStore`` in
        one branch and a ``ChainStore`` in the other, and every chain
        the graph can prove goes through the shared base surface."""
        if a is None or b is None:
            return None
        if a == b:
            return a
        b_anc = self._ancestors(b)
        for cand in self._ancestors(a):
            if cand in b_anc:
                return cand
        return None

    def _value_class(self, rel: str, value: ast.AST) -> tuple[str, str] | None:
        """(rel, class) when ``value`` is structurally a constructor
        call of a package class (possibly behind ``or`` / a
        conditional expression)."""
        if isinstance(value, (ast.BoolOp, ast.IfExp)):
            operands = (
                value.values
                if isinstance(value, ast.BoolOp)
                else [value.body, value.orelse]
            )
            hits = {
                h
                for h in (self._value_class(rel, v) for v in operands)
                if h is not None
            }
            if not hits:
                return None
            merged = hits.pop()
            for h in hits:
                merged = self._unify_classes(merged, h)
                if merged is None:
                    return None
            return merged
        if not isinstance(value, ast.Call):
            return None
        dotted = dotted_name(value.func)
        if dotted is None:
            return None
        return self._class_by_dotted(rel, dotted)

    def _class_by_dotted(self, rel: str, dotted: str) -> tuple[str, str] | None:
        parts = dotted.split(".")
        if len(parts) == 1:
            # follow re-export chains (package __init__.py fronts most
            # of the class surface: ``from p1_tpu.chain import Chain``)
            name, seen = parts[0], set()
            while (rel, name) not in seen:
                seen.add((rel, name))
                if name in self._classes.get(rel, {}):
                    return (rel, name)
                imp = self._imports.get(rel, {}).get(name)
                if imp and imp[0] == "obj":
                    rel, name = imp[1], imp[2]
                    continue
                return None
            return None
        # mod.Class (module alias, or a full p1_tpu.x.y.Class path)
        for split in range(len(parts) - 1, 0, -1):
            head, tail = ".".join(parts[:split]), parts[split:]
            imp = self._imports.get(rel, {}).get(head)
            if imp and imp[0] == "module" and len(tail) == 1:
                if tail[0] in self._classes.get(imp[1], {}):
                    return (imp[1], tail[0])
        return None

    # -- effect collection ----------------------------------------------

    def _collect_effects(self, rel: str, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._summarize(rel, None, stmt)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._summarize(rel, stmt.name, sub)

    def _summarize(self, rel: str, cls: str | None, fn: ast.AST) -> None:
        name = f"{cls}.{fn.name}" if cls else fn.name
        node = self.nodes[f"{rel}::{name}"]
        for sub in sorted(walk_no_nested_defs(fn), key=sort_key):
            if isinstance(sub, ast.Await):
                node.awaits.append(sort_key(sub))
            elif isinstance(sub, ast.Call):
                dotted = dotted_name(sub.func)
                if dotted is None:
                    continue
                prim = self._blocking_primitive(rel, dotted)
                if prim is not None:
                    node.blocking.append((prim, sub.lineno))
                target = self._resolve_call(rel, cls, dotted)
                node.calls.append(
                    CallSite(dotted=dotted, target=target, line=sub.lineno)
                )
            elif (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in WATCHED_STATE
            ):
                if isinstance(sub.ctx, ast.Load):
                    node.state_reads.append((sub.attr, sort_key(sub)))
                elif isinstance(sub.ctx, ast.Store):
                    node.state_writes.append((sub.attr, sort_key(sub)))
        node.set_locals = local_set_bindings(fn)

    def _blocking_primitive(self, rel: str, dotted: str) -> str | None:
        if dotted == "open":
            return "open"
        if dotted in BLOCKING_DOTTED:
            return dotted
        if dotted.startswith("subprocess."):
            return dotted
        if dotted == "ctypes.CDLL" or dotted.startswith("ctypes.CDLL."):
            return "ctypes.CDLL"
        head = dotted.split(".", 1)[0]
        if "." in dotted and head in self._cdll_handles.get(rel, ()):
            return f"ctypes:{dotted}"
        return None

    def _resolve_call(
        self, rel: str, cls: str | None, dotted: str
    ) -> str | None:
        parts = dotted.split(".")
        # strip a call link ("factory().run") — not resolvable here
        if any("()" in p for p in parts):
            return None
        if len(parts) == 1:
            name = parts[0]
            hit = self._locals.get(rel, {}).get(name)
            if hit:
                return hit
            if name in self._classes.get(rel, {}):
                return self._classes[rel][name].get("__init__")
            imp = self._imports.get(rel, {}).get(name)
            if imp and imp[0] == "obj":
                return self._resolve_obj(imp[1], imp[2])
            return None
        if parts[0] in ("self", "cls") and cls is not None:
            if len(parts) == 2:
                return self._resolve_method(rel, cls, parts[1])
            if len(parts) == 3:
                typ = self._attr_types.get(rel, {}).get(cls, {}).get(
                    parts[1]
                )
                if typ is not None:
                    return self._resolve_method(typ[0], typ[1], parts[2])
            return None
        # ClassName.method in this module or an imported class
        hit = self._class_by_dotted(rel, ".".join(parts[:-1]))
        if hit is not None:
            return self._resolve_method(hit[0], hit[1], parts[-1])
        # mod.func through an imported module (any alias depth)
        for split in range(len(parts) - 1, 0, -1):
            head, tail = ".".join(parts[:split]), parts[split:]
            imp = self._imports.get(rel, {}).get(head)
            if imp and imp[0] == "module" and len(tail) == 1:
                return self._resolve_obj(imp[1], tail[0])
        return None

    def _resolve_obj(self, rel: str, name: str) -> str | None:
        hit = self._locals.get(rel, {}).get(name)
        if hit:
            return hit
        if name in self._classes.get(rel, {}):
            return self._classes[rel][name].get("__init__")
        imp = self._imports.get(rel, {}).get(name)  # re-export
        if imp and imp[0] == "obj":
            return self._resolve_obj(imp[1], imp[2])
        return None

    def _resolve_method(self, rel: str, cls: str, meth: str) -> str | None:
        """Method lookup through the class and its package-resolvable
        bases (declaration order — Python's MRO for the single-
        inheritance shapes this package uses)."""
        seen: set[tuple[str, str]] = set()
        stack = [(rel, cls)]
        while stack:
            cur = stack.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            crel, cname = cur
            hit = self._classes.get(crel, {}).get(cname, {}).get(meth)
            if hit:
                return hit
            for base in self._bases.get(crel, {}).get(cname, ()):
                bhit = self._class_by_dotted(crel, base)
                if bhit is not None:
                    stack.append(bhit)
        return None

    # -- blocking fixed point -------------------------------------------

    def blocking_paths(self) -> dict[str, BlockingWitness]:
        """qual -> witness for every node that reaches a blocking
        primitive through its own control flow or any resolved callee
        chain.  Monotone fixed point over call edges; each node keeps
        the first witness it acquired (stable across runs — nodes and
        calls are iterated in sorted/source order).

        Propagation crosses an edge only when the callee is SYNC: a
        sync callee's body executes inline at the call, while merely
        calling an ``async def`` builds a coroutine without running it
        — the await that eventually runs it belongs to (and is flagged
        at) the async frame that does the awaiting."""
        witness: dict[str, BlockingWitness] = {}
        for qual in sorted(self.nodes):
            node = self.nodes[qual]
            if node.blocking:
                prim, line = node.blocking[0]
                witness[qual] = BlockingWitness(prim, line, None)
        changed = True
        while changed:
            changed = False
            for qual in sorted(self.nodes):
                if qual in witness:
                    continue
                for call in self.nodes[qual].calls:
                    if (
                        call.target in witness
                        and not self.nodes[call.target].is_async
                    ):
                        tail = witness[call.target]
                        witness[qual] = BlockingWitness(
                            tail.primitive, call.line, call.target
                        )
                        changed = True
                        break
        return witness

    def witness_chain(
        self, qual: str, witness: dict[str, BlockingWitness]
    ) -> list[str]:
        """Human call path: ["Node._handle_block", "check_block",
        ..., "os.fsync"] for the finding detail."""
        chain = [self.nodes[qual].name]
        seen = {qual}
        cur = witness.get(qual)
        while cur is not None and cur.via is not None:
            if cur.via in seen:  # defensive: recursion in the witness
                break
            seen.add(cur.via)
            chain.append(self.nodes[cur.via].name)
            cur = witness.get(cur.via)
        chain.append(cur.primitive if cur is not None else "?")
        return chain


def local_set_bindings(scope: ast.AST) -> frozenset[str]:
    """Local names in ``scope`` (a function def or module) EVERY
    binding of which is structurally a set expression — the one-
    dataflow-hop summary the upgraded ``set-iteration`` rule and the
    call-graph node summaries share.

    Deliberately an under-approximation: any binding the classifier
    cannot prove a set (a parameter, a for/with target, tuple
    unpacking, a reassignment to ``sorted(...)``) disqualifies the
    name, so ``s = set(...); s = sorted(s)`` stays clean."""
    set_bound: dict[str, bool] = {}
    for sub in walk_no_nested_defs(scope):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            targets = (
                sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            )
            value = sub.value
            for tgt in targets:
                if isinstance(tgt, ast.Name) and value is not None:
                    isset = is_set_expr(value)
                    set_bound[tgt.id] = isset and set_bound.get(tgt.id, True)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for el in ast.walk(tgt):
                        if isinstance(el, ast.Name):
                            set_bound[el.id] = False
        elif isinstance(sub, ast.NamedExpr) and isinstance(
            sub.target, ast.Name
        ):
            isset = is_set_expr(sub.value)
            set_bound[sub.target.id] = isset and set_bound.get(
                sub.target.id, True
            )
        elif isinstance(sub, (ast.For, ast.AsyncFor)):
            for el in ast.walk(sub.target):
                if isinstance(el, ast.Name):
                    set_bound[el.id] = False
        elif isinstance(sub, ast.withitem) and sub.optional_vars:
            for el in ast.walk(sub.optional_vars):
                if isinstance(el, ast.Name):
                    set_bound[el.id] = False
        elif isinstance(sub, ast.ExceptHandler) and sub.name:
            set_bound[sub.name] = False
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                set_bound[alias.asname or alias.name.split(".")[0]] = False
    args = getattr(scope, "args", None)
    if args is not None:
        for a in (
            *args.posonlyargs,
            *args.args,
            *args.kwonlyargs,
            *([args.vararg] if args.vararg else []),
            *([args.kwarg] if args.kwarg else []),
        ):
            set_bound[a.arg] = False
    return frozenset(n for n, isset in set_bound.items() if isset)


def _contains_cdll(value: ast.AST) -> bool:
    for node in ast.walk(value):
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
            "ctypes.CDLL",
            "CDLL",
        ):
            return True
    return False


def iter_functions(tree: ast.Module) -> Iterator[tuple[str | None, ast.AST]]:
    """(class name | None, def) for every top-level function and
    method in a module — the shared walk order the graph uses."""
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, stmt
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield stmt.name, sub
