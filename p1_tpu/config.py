"""Node/miner configuration (SURVEY.md §7 step 7: one config dataclass).

Everything a node process needs: chain parameters, hash backend choice,
p2p identity and peer list, persistence path, mining switches.  The CLI
(p1_tpu/cli.py) builds one of these from flags; tests build them directly.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class NodeConfig:
    difficulty: int = 16
    backend: str = "cpu"  # hash backend registry name (cpu/numpy/jax/sharded)
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral (tests); CLI defaults to 9444
    peers: tuple[str, ...] = ()  # "host:port" dial targets
    mine: bool = True
    store_path: str | None = None  # chain log; None = in-memory only
    max_block_txs: int = 1000
    batch: int | None = None  # device batch override for jax/sharded
    chunk: int | None = None  # miner abort granularity (nonces per call)
    #: Coinbase recipient id.  None = a random per-process id, which is what
    #: makes two independent miners produce *different* candidate blocks.
    miner_id: str | None = None
    #: Opt-in difficulty retargeting (core/retarget.py).  0 = fixed
    #: difficulty (every benchmark config).  Both must be set together;
    #: the pair is part of chain identity (committed into genesis).
    retarget_window: int = 0
    target_spacing: int = 0
    #: Gossip blocks carrying transactions as compact blocks (header +
    #: txids, ~32 B/tx) instead of full serializations; receivers
    #: reconstruct from their mempool and fetch only what they lack.
    #: Local preference, not a chain parameter — mixed nets interoperate.
    compact_gossip: bool = True
    #: Age after which a pending transaction is dropped from the pool
    #: (hygiene — an unmineable spend must not occupy capacity forever;
    #: the owner can always rebroadcast).  0 disables expiry.
    mempool_ttl_s: float = 3600.0
    #: Peer discovery out-degree: when > 0, the node dials addresses
    #: learned via GETADDR/ADDR gossip until it holds this many
    #: connections — one seed peer bootstraps the whole network.  0 (the
    #: default) dials only the configured ``peers``; the address book and
    #: GETADDR serving stay on either way.
    target_peers: int = 0
    #: Liveness deadlines (node/node.py peer sessions).  A connection must
    #: complete its HELLO within ``handshake_timeout_s`` or it is reaped —
    #: a pre-handshake socket holds node resources but proves nothing.
    #: After the handshake, a peer silent for ``ping_interval_s`` gets a
    #: PING probe; silence through another ``pong_timeout_s`` means
    #: eviction and the ``MAX_PEERS`` slot is reused.  ANY received frame
    #: counts as liveness, so chatty peers are never probed at all.
    handshake_timeout_s: float = 10.0
    ping_interval_s: float = 60.0
    pong_timeout_s: float = 20.0
    #: Request supervision (node/supervision.py).  An in-flight multi-round
    #: fetch (locator block sync, compact-block GETBLOCKTXN round, paged
    #: mempool sync) must show *progress* — blocks accepted, pages
    #: consumed, not mere liveness — within ``sync_stall_timeout_s`` or
    #: the node re-issues the request to a different eligible peer and
    #: demotes (never bans) the staller.  Failovers back off with jitter
    #: from ``sync_backoff_base_s`` doubling up to ``sync_backoff_max_s``,
    #: and at most ``sync_attempts_max`` consecutive stalls are chased per
    #: catch-up episode (progress resets the budget).  The deadline is
    #: deliberately far above any honest batch turnaround: a slow peer
    #: that keeps landing blocks is never demoted.
    sync_stall_timeout_s: float = 10.0
    sync_attempts_max: int = 8
    sync_backoff_base_s: float = 0.25
    sync_backoff_max_s: float = 5.0
    #: Escape hatch for the storage durability layer: by default a store
    #: write failure (ENOSPC, EIO, fsync error) degrades the node into a
    #: serve-only mode that retries the disk with backoff and recovers
    #: in place; with this set the node signals fatal instead (the CLI
    #: exits 4) for operators who prefer a supervisor restart.
    store_degraded_exit: bool = False
    #: Overload resilience (node/governor.py).  ``admission_control``
    #: gates the per-peer multi-class token buckets at the dispatch door
    #: (blocks / txs / queries — floods are dropped and escalate to the
    #: misbehavior score; solicited replies are never charged).  On by
    #: default: the budgets sit far above any honest peer's rates.
    admission_control: bool = True
    #: High watermark (bytes) on the node's accounted memory gauge
    #: (resident chain bodies + pending pool bytes + peer write
    #: buffers).  Above it the node enters the SHED overload state —
    #: low-priority gossip and mempool pages drop, mining pauses,
    #: consensus-critical headers/blocks/proof service keeps running —
    #: with hysteresis back to NORMAL below 80% of the mark.  0 (the
    #: default) disables shedding; admission control and the per-peer
    #: write-queue caps stay on regardless.
    mem_watermark_bytes: int = 0
    #: Memory-bounded operation: keep only the most recent N main-chain
    #: block BODIES resident in the RAM index (headers and all metadata
    #: stay), evicting older bodies once they are durably refetchable
    #: from the append-only store and re-reading them on demand.  Cuts
    #: steady-state and resume peak RSS from O(chain) to O(N)
    #: (docs/PERF.md "Memory-bounded operation").  0 disables (fully
    #: resident — the historical behavior); requires ``store_path``.
    body_cache_blocks: int = 0
    #: Segmented store layout (chain/segstore.py): shard the append-only
    #: log into bounded segment files of this many bytes (per-segment
    #: fsck/compaction/pruning — the archive-scale layout).  0 keeps
    #: whatever layout the store already has (an existing segmented
    #: store reopens segmented; a fresh or single-file store stays
    #: single-file).  A single-file store upgrades LOSSLESSLY on the
    #: first writer acquire when this is set.
    store_segment_bytes: int = 0
    #: Pruned mode (round 18): keep at least this many recent block
    #: BODIES on disk and discard whole body segments below the latest
    #: snapshot checkpoint — the node keeps serving headers, cached
    #: filters, and snapshots, and REFUSES (without disconnecting)
    #: block-sync requests into the pruned range; honest joiners fail
    #: over to an archive peer or snapshot-sync.  0 disables (archive
    #: node — the default).  Requires (and implies) a segmented store.
    prune_keep_blocks: int = 0
    #: Validation fast lane (core/keys.py): worker-pool size for batched
    #: Ed25519 verification on the untrusted paths (revalidation,
    #: foreign-store loads, deep-sync batches).  0 = auto (the
    #: ``P1_VERIFY_WORKERS`` env var, else ``os.cpu_count()``) — with
    #: the ``cryptography`` wheel the backend releases the GIL inside
    #: OpenSSL, so workers give real multi-core parallelism; the
    #: pure-Python fallback batches via one multi-scalar multiplication
    #: per window instead.  Worker count NEVER changes validation
    #: outcomes, only where the verify cost is paid.
    verify_workers: int = 0
    #: Staged block pipeline (node/pipeline.py, round 19): off-loop
    #: worker lanes for the validate and store stages.  0 (the default)
    #: keeps the historical inline node — every stage on the event
    #: loop, scheduling byte-identical to before the refactor.  N >= 1
    #: moves batched signature pre-verification and the whole fsync
    #: chain (append, checkpoints, snapshot flips) onto worker threads
    #: and, when ``verify_workers`` is 0, sizes the Ed25519 verify pool
    #: to N.  Staging NEVER changes validation outcomes or wire
    #: behavior — the network simulator proves the trace digest is
    #: byte-identical with staging on or off — only where the CPU/IO
    #: cost is paid.
    pipeline_workers: int = 0
    #: Signature-verification backend (core/keys.py ladder, round 15).
    #: "auto" (default) resolves wheel > native C++ engine > pure-Python
    #: fallback; "cryptography"/"native" pin a rung (degrading down the
    #: ladder with a warning if unavailable), "fallback" forces the
    #: pure-Python tier, "device" opts batches into the JAX mesh
    #: multi-scalar multiplication (hashx/ed25519_msm.py — a win on real
    #: multi-chip meshes, not host CPUs).  Backend choice NEVER changes
    #: validation outcomes — every rung is verdict- and error-text-
    #: equivalent by test — only the cost model.
    sig_backend: str = "auto"
    #: Deterministic identity/jitter seed.  None (production) draws the
    #: HELLO instance nonce and default miner id from the OS and leaves
    #: supervision backoff jitter on an unseeded RNG; a seed makes all
    #: of it a pure function of the seed — what lets the network
    #: simulator (node/netsim.py) replay a thousand-node run
    #: byte-for-byte.  Never affects consensus: identity and jitter
    #: only.
    rng_seed: int | None = None
    #: Untrusted snapshot sync (chain/snapshot.py, the assumeUTXO
    #: analog).  When True, a FRESH node (height 0) whose peer
    #: advertises a tip at least ``snapshot_min_lead`` blocks ahead
    #: fetches a ledger-state snapshot instead of replaying history:
    #: it verifies the manifest/chunk digests/state root, starts
    #: serving queries immediately in the ASSUMED validation state, and
    #: revalidates the real history in the background — flipping to
    #: fully-validated on a matching state root, or quarantining the
    #: snapshot, demoting the serving peer, and falling back to genesis
    #: IBD on any divergence.  Off by default: assumed state is a trust
    #: posture an operator must opt into.
    snapshot_sync: bool = False
    #: Minimum advertised-height lead before a snapshot is preferred
    #: over ordinary IBD (a snapshot round trip is pointless for a
    #: nearly caught-up peer).
    snapshot_min_lead: int = 4
    #: State-root checkpoint spacing override (0 = the chain default:
    #: the retarget window when one is active, else
    #: chain/snapshot.py DEFAULT_CHECKPOINT_INTERVAL).  Must agree
    #: across nodes for served snapshot heights to line up with what
    #: joiners can revalidate; it is a policy knob, never consensus.
    snapshot_interval: int = 0
    #: Telemetry plane (node/telemetry.py): per-stage latency histograms
    #: over the block pipeline (admission/validate/store/relay), query
    #: request latency, and supervision backoff timing, exported over
    #: GETMETRICS / `p1 metrics`.  Counters (NodeMetrics/status()) stay
    #: live either way; False removes every telemetry clock read —
    #: recording is observer-only by contract (the sim determinism pair
    #: proves the trace digest is identical in both states), so this
    #: knob exists for overhead control, not correctness.
    telemetry: bool = True
    #: Re-run the full stateless validation (PoW, merkle, Ed25519) over
    #: every stored block at boot instead of the trusted fast resume.
    #: The store is this node's own flocked append-only log of blocks it
    #: already validated, so the default trusts it (~3x faster boots at
    #: 100k blocks, docs/PERF.md); set True when on-disk integrity is in
    #: question.
    revalidate_store: bool = False
    #: Version-bits protocol evolution (chain/versionbits.py, the BIP9
    #: analog, round 20): named feature deployments as
    #: ``(name, bit, start_height, timeout_height)`` tuples.  Miners
    #: aware of a deployment signal its bit in mined header versions
    #: while it is STARTED/LOCKED_IN; activation is a pure function of
    #: the header chain, so every configured node reports the same
    #: state at the same height.  Empty (the default) mines the legacy
    #: ``version=1`` headers — byte-identical to every earlier round.
    #: Header version is NOT a consensus field, so mixed
    #: configured/legacy meshes never fork on signaling alone (the
    #: ``version_activation`` scenario pins this).
    deployments: tuple = ()
    #: Signaling window length in blocks and the signal count within one
    #: completed window that locks a deployment in.  Like
    #: ``snapshot_interval``: must agree across nodes for their state
    #: reports to line up — policy coordination, never consensus.
    vb_window: int = 8
    vb_threshold: int = 6
    #: Set-reconciliation tx relay (node/reconcile.py, the Erlay
    #: analog, round 23).  Off by default: flood relay stays the
    #: baseline behavior and every pre-recon sim trace is untouched.
    #: When on, accepted transactions queue into per-peer pending
    #: windows and periodic sketch rounds exchange only the symmetric
    #: difference; flood remains the fallback (decode failure, demoted
    #: poisoners, non-recon peers) and block announces always flood.
    #: Local relay policy, never consensus — but a deployment named
    #: ``txrecon`` in ``deployments`` additionally gates activation on
    #: the version-bits plane reaching ACTIVE, so a mesh can roll the
    #: feature out by miner signaling with stragglers staying correct.
    recon_gossip: bool = False
    #: Seconds between reconciliation rounds (one outbound peer per
    #: tick, round-robin).  Bounds tx propagation latency over
    #: reconciled links at roughly diameter * interval in the worst
    #: case; the flood spine below keeps the common case flood-fast.
    recon_interval_s: float = 1.0
    #: Low-latency flood spine: relay each accepted tx by ordinary
    #: flood to this many outbound reconciling peers (dial order, so
    #: the spine is deterministic) and reconcile the rest.  Erlay's
    #: shape: flooding a few links spans the mesh fast; sketches carry
    #: the redundant copies that were the bandwidth bill.
    recon_flood_degree: int = 1

    def retarget_rule(self):
        """The chain's ``RetargetRule``, or None for fixed difficulty."""
        from p1_tpu.core.retarget import RetargetRule

        return RetargetRule.from_params(
            self.retarget_window, self.target_spacing
        )

    def peer_addrs(self) -> list[tuple[str, int]]:
        # A bare "host:port" string would otherwise iterate character-wise.
        peers = (self.peers,) if isinstance(self.peers, str) else self.peers
        out = []
        for peer in peers:
            host, _, port = peer.rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
        return out
