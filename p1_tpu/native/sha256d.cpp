// Native SHA-256d core for the "native" hash backend.
//
// Capability parity: the reference's CPU mining path as a native-code
// component (SURVEY.md §2 requires C++ equivalents wherever the reference
// is native; BASELINE.json:5 names the CPU backend the TPU must beat).
// This is the host-side performance tier between the hashlib loop
// (~0.8 MH/s, Python-call-bound) and the TPU kernel: a single C call scans
// a whole nonce range with the midstate trick, using the x86 SHA-NI
// extension when the CPU has it (~10-20x hashlib) and a portable scalar
// compression otherwise.
//
// Exposed C ABI (ctypes-friendly; see p1_tpu/hashx/native_backend.py):
//   p1_sha256d(data, len, out32)           - one double-SHA-256
//   p1_search(prefix76, start, count, d)   - earliest nonce with >= d
//                                            leading zero bits, or -1
//   p1_has_shani()                         - which compression runs
//
// The header layout contract matches p1_tpu/core/header.py: 80-byte
// big-endian header, nonce in bytes 76..80; the search holds bytes 0..76
// fixed (one compression of bytes 0..64 is hoisted out of the loop).

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#include <cpuid.h>
#define P1_X86 1
#else
#define P1_X86 0
#endif

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

constexpr uint32_t IV[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }
inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
inline void put_be32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

// ---------------------------------------------------------------- scalar --

void compress_scalar(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i) w[i] = be32(block + 4 * i);
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + K[i] + w[i];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

// ---------------------------------------------------------------- SHA-NI --

#if P1_X86
// Standard two-lane SHA-NI schedule (state held as ABEF/CDGH vectors);
// compiled with a target attribute so the .so builds and loads on any
// x86-64 and the choice happens at runtime via __builtin_cpu_supports.
__attribute__((target("sha,sse4.1")))
void compress_shani(uint32_t state[8], const uint8_t block[64]) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);

  __m128i TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[0]));
  __m128i STATE1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[4]));
  TMP = _mm_shuffle_epi32(TMP, 0xB1);        // CDAB
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);  // EFGH
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);  // ABEF
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);       // CDGH

  const __m128i ABEF_SAVE = STATE0;
  const __m128i CDGH_SAVE = STATE1;
  __m128i MSG, MSG0, MSG1, MSG2, MSG3;

#define P1_QROUND(Ki_lo, Ki_hi, M)                                   \
  do {                                                               \
    MSG = _mm_add_epi32(                                             \
        M, _mm_set_epi64x((long long)(Ki_hi), (long long)(Ki_lo)));  \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);             \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                              \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);             \
  } while (0)

  // Rounds 0-15: raw message words; start msg1 pre-passes as groups land.
  MSG0 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 0)), MASK);
  P1_QROUND(0x71374491428a2f98ULL, 0xe9b5dba5b5c0fbcfULL, MSG0);
  MSG1 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 16)), MASK);
  P1_QROUND(0x59f111f13956c25bULL, 0xab1c5ed5923f82a4ULL, MSG1);
  MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);
  MSG2 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 32)), MASK);
  P1_QROUND(0x12835b01d807aa98ULL, 0x550c7dc3243185beULL, MSG2);
  MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);
  MSG3 = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(block + 48)), MASK);
  P1_QROUND(0x80deb1fe72be5d74ULL, 0xc19bf1749bdc06a7ULL, MSG3);

  // Schedule step: Mnext (already msg1-combined with its successor two
  // steps ago) gains w[i-7..i-4] = alignr(newest, second_newest) and the
  // msg2 sigma-1 chain; second_newest then takes ITS msg1 pre-pass.  The
  // alignr must read second_newest RAW, so msg1 comes last.
#define P1_SCHED(Mnext, Mprev2, Mprev1)                              \
  do {                                                               \
    TMP = _mm_alignr_epi8(Mprev1, Mprev2, 4);                        \
    Mnext = _mm_add_epi32(Mnext, TMP);                               \
    Mnext = _mm_sha256msg2_epu32(Mnext, Mprev1);                     \
    Mprev2 = _mm_sha256msg1_epu32(Mprev2, Mprev1);                   \
  } while (0)

  // Rounds 16-63: 12 schedule+round pairs with cyclically rotating roles.
  P1_SCHED(MSG0, MSG2, MSG3);
  P1_QROUND(0xefbe4786e49b69c1ULL, 0x240ca1cc0fc19dc6ULL, MSG0);
  P1_SCHED(MSG1, MSG3, MSG0);
  P1_QROUND(0x4a7484aa2de92c6fULL, 0x76f988da5cb0a9dcULL, MSG1);
  P1_SCHED(MSG2, MSG0, MSG1);
  P1_QROUND(0xa831c66d983e5152ULL, 0xbf597fc7b00327c8ULL, MSG2);
  P1_SCHED(MSG3, MSG1, MSG2);
  P1_QROUND(0xd5a79147c6e00bf3ULL, 0x1429296706ca6351ULL, MSG3);
  P1_SCHED(MSG0, MSG2, MSG3);
  P1_QROUND(0x2e1b213827b70a85ULL, 0x53380d134d2c6dfcULL, MSG0);
  P1_SCHED(MSG1, MSG3, MSG0);
  P1_QROUND(0x766a0abb650a7354ULL, 0x92722c8581c2c92eULL, MSG1);
  P1_SCHED(MSG2, MSG0, MSG1);
  P1_QROUND(0xa81a664ba2bfe8a1ULL, 0xc76c51a3c24b8b70ULL, MSG2);
  P1_SCHED(MSG3, MSG1, MSG2);
  P1_QROUND(0xd6990624d192e819ULL, 0x106aa070f40e3585ULL, MSG3);
  P1_SCHED(MSG0, MSG2, MSG3);
  P1_QROUND(0x1e376c0819a4c116ULL, 0x34b0bcb52748774cULL, MSG0);
  P1_SCHED(MSG1, MSG3, MSG0);
  P1_QROUND(0x4ed8aa4a391c0cb3ULL, 0x682e6ff35b9cca4fULL, MSG1);
  P1_SCHED(MSG2, MSG0, MSG1);
  P1_QROUND(0x78a5636f748f82eeULL, 0x8cc7020884c87814ULL, MSG2);
  P1_SCHED(MSG3, MSG1, MSG2);
  P1_QROUND(0xa4506ceb90befffaULL, 0xc67178f2bef9a3f7ULL, MSG3);

#undef P1_SCHED
#undef P1_QROUND

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);

  TMP = _mm_shuffle_epi32(STATE0, 0x1B);        // FEBA
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);     // DCHG
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);  // DCBA
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);     // HGFE... -> EFGH order below

  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[0]), STATE0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[4]), STATE1);
}
// Two-lane interleaved SHA-NI compression: two independent (state, block)
// pairs advanced in lockstep.  The single-lane routine is LATENCY-bound —
// each sha256rnds2 depends on the previous one, so the ~4-6 cycle
// instruction latency gates throughput while issue slots idle.  Header
// digests in a chain verify are mutually independent, so interleaving two
// of them fills those slots and nearly doubles verified headers/s
// (measured in benchmarks/host_ingest.py; parity-fuzzed against the
// hashlib oracle like every other engine path).  The K constants load
// once per quad-round and feed both lanes.
__attribute__((target("sha,sse4.1")))
void compress_shani2(uint32_t sa[8], const uint8_t* ba, uint32_t sb[8],
                     const uint8_t* bb) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);

  __m128i TMPa = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&sa[0]));
  __m128i S1a = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&sa[4]));
  TMPa = _mm_shuffle_epi32(TMPa, 0xB1);
  S1a = _mm_shuffle_epi32(S1a, 0x1B);
  __m128i S0a = _mm_alignr_epi8(TMPa, S1a, 8);
  S1a = _mm_blend_epi16(S1a, TMPa, 0xF0);
  __m128i TMPb = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&sb[0]));
  __m128i S1b = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&sb[4]));
  TMPb = _mm_shuffle_epi32(TMPb, 0xB1);
  S1b = _mm_shuffle_epi32(S1b, 0x1B);
  __m128i S0b = _mm_alignr_epi8(TMPb, S1b, 8);
  S1b = _mm_blend_epi16(S1b, TMPb, 0xF0);

  const __m128i ABEF_SAVEa = S0a, CDGH_SAVEa = S1a;
  const __m128i ABEF_SAVEb = S0b, CDGH_SAVEb = S1b;
  __m128i MSGa, MSGb;
  __m128i M0a, M1a, M2a, M3a, M0b, M1b, M2b, M3b;

#define P1_QROUND2(Ki_lo, Ki_hi, Ma, Mb)                             \
  do {                                                               \
    const __m128i KV =                                               \
        _mm_set_epi64x((long long)(Ki_hi), (long long)(Ki_lo));      \
    MSGa = _mm_add_epi32(Ma, KV);                                    \
    MSGb = _mm_add_epi32(Mb, KV);                                    \
    S1a = _mm_sha256rnds2_epu32(S1a, S0a, MSGa);                     \
    S1b = _mm_sha256rnds2_epu32(S1b, S0b, MSGb);                     \
    MSGa = _mm_shuffle_epi32(MSGa, 0x0E);                            \
    MSGb = _mm_shuffle_epi32(MSGb, 0x0E);                            \
    S0a = _mm_sha256rnds2_epu32(S0a, S1a, MSGa);                     \
    S0b = _mm_sha256rnds2_epu32(S0b, S1b, MSGb);                     \
  } while (0)

  M0a = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(ba + 0)), MASK);
  M0b = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(bb + 0)), MASK);
  P1_QROUND2(0x71374491428a2f98ULL, 0xe9b5dba5b5c0fbcfULL, M0a, M0b);
  M1a = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(ba + 16)), MASK);
  M1b = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(bb + 16)), MASK);
  P1_QROUND2(0x59f111f13956c25bULL, 0xab1c5ed5923f82a4ULL, M1a, M1b);
  M0a = _mm_sha256msg1_epu32(M0a, M1a);
  M0b = _mm_sha256msg1_epu32(M0b, M1b);
  M2a = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(ba + 32)), MASK);
  M2b = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(bb + 32)), MASK);
  P1_QROUND2(0x12835b01d807aa98ULL, 0x550c7dc3243185beULL, M2a, M2b);
  M1a = _mm_sha256msg1_epu32(M1a, M2a);
  M1b = _mm_sha256msg1_epu32(M1b, M2b);
  M3a = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(ba + 48)), MASK);
  M3b = _mm_shuffle_epi8(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(bb + 48)), MASK);
  P1_QROUND2(0x80deb1fe72be5d74ULL, 0xc19bf1749bdc06a7ULL, M3a, M3b);

#define P1_SCHED2(Mnext_a, Mprev2_a, Mprev1_a, Mnext_b, Mprev2_b, Mprev1_b) \
  do {                                                               \
    TMPa = _mm_alignr_epi8(Mprev1_a, Mprev2_a, 4);                   \
    Mnext_a = _mm_add_epi32(Mnext_a, TMPa);                          \
    Mnext_a = _mm_sha256msg2_epu32(Mnext_a, Mprev1_a);               \
    Mprev2_a = _mm_sha256msg1_epu32(Mprev2_a, Mprev1_a);             \
    TMPb = _mm_alignr_epi8(Mprev1_b, Mprev2_b, 4);                   \
    Mnext_b = _mm_add_epi32(Mnext_b, TMPb);                          \
    Mnext_b = _mm_sha256msg2_epu32(Mnext_b, Mprev1_b);               \
    Mprev2_b = _mm_sha256msg1_epu32(Mprev2_b, Mprev1_b);             \
  } while (0)

  P1_SCHED2(M0a, M2a, M3a, M0b, M2b, M3b);
  P1_QROUND2(0xefbe4786e49b69c1ULL, 0x240ca1cc0fc19dc6ULL, M0a, M0b);
  P1_SCHED2(M1a, M3a, M0a, M1b, M3b, M0b);
  P1_QROUND2(0x4a7484aa2de92c6fULL, 0x76f988da5cb0a9dcULL, M1a, M1b);
  P1_SCHED2(M2a, M0a, M1a, M2b, M0b, M1b);
  P1_QROUND2(0xa831c66d983e5152ULL, 0xbf597fc7b00327c8ULL, M2a, M2b);
  P1_SCHED2(M3a, M1a, M2a, M3b, M1b, M2b);
  P1_QROUND2(0xd5a79147c6e00bf3ULL, 0x1429296706ca6351ULL, M3a, M3b);
  P1_SCHED2(M0a, M2a, M3a, M0b, M2b, M3b);
  P1_QROUND2(0x2e1b213827b70a85ULL, 0x53380d134d2c6dfcULL, M0a, M0b);
  P1_SCHED2(M1a, M3a, M0a, M1b, M3b, M0b);
  P1_QROUND2(0x766a0abb650a7354ULL, 0x92722c8581c2c92eULL, M1a, M1b);
  P1_SCHED2(M2a, M0a, M1a, M2b, M0b, M1b);
  P1_QROUND2(0xa81a664ba2bfe8a1ULL, 0xc76c51a3c24b8b70ULL, M2a, M2b);
  P1_SCHED2(M3a, M1a, M2a, M3b, M1b, M2b);
  P1_QROUND2(0xd6990624d192e819ULL, 0x106aa070f40e3585ULL, M3a, M3b);
  P1_SCHED2(M0a, M2a, M3a, M0b, M2b, M3b);
  P1_QROUND2(0x1e376c0819a4c116ULL, 0x34b0bcb52748774cULL, M0a, M0b);
  P1_SCHED2(M1a, M3a, M0a, M1b, M3b, M0b);
  P1_QROUND2(0x4ed8aa4a391c0cb3ULL, 0x682e6ff35b9cca4fULL, M1a, M1b);
  P1_SCHED2(M2a, M0a, M1a, M2b, M0b, M1b);
  P1_QROUND2(0x78a5636f748f82eeULL, 0x8cc7020884c87814ULL, M2a, M2b);
  P1_SCHED2(M3a, M1a, M2a, M3b, M1b, M2b);
  P1_QROUND2(0xa4506ceb90befffaULL, 0xc67178f2bef9a3f7ULL, M3a, M3b);

#undef P1_SCHED2
#undef P1_QROUND2

  S0a = _mm_add_epi32(S0a, ABEF_SAVEa);
  S1a = _mm_add_epi32(S1a, CDGH_SAVEa);
  TMPa = _mm_shuffle_epi32(S0a, 0x1B);
  S1a = _mm_shuffle_epi32(S1a, 0xB1);
  S0a = _mm_blend_epi16(TMPa, S1a, 0xF0);
  S1a = _mm_alignr_epi8(S1a, TMPa, 8);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&sa[0]), S0a);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&sa[4]), S1a);

  S0b = _mm_add_epi32(S0b, ABEF_SAVEb);
  S1b = _mm_add_epi32(S1b, CDGH_SAVEb);
  TMPb = _mm_shuffle_epi32(S0b, 0x1B);
  S1b = _mm_shuffle_epi32(S1b, 0xB1);
  S0b = _mm_blend_epi16(TMPb, S1b, 0xF0);
  S1b = _mm_alignr_epi8(S1b, TMPb, 8);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&sb[0]), S0b);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&sb[4]), S1b);
}
#endif  // P1_X86

using CompressFn = void (*)(uint32_t[8], const uint8_t[64]);
using Compress2Fn = void (*)(uint32_t[8], const uint8_t*, uint32_t[8],
                             const uint8_t*);

CompressFn pick_compress() {
#if P1_X86
  // Raw CPUID rather than __builtin_cpu_supports("sha"): GCC (through at
  // least 13) rejects "sha" as a feature name — it is a clang extension —
  // and the builtin is not worth losing buildability on half the
  // toolchains.  SHA extensions: CPUID.(EAX=7,ECX=0):EBX bit 29;
  // SSE4.1: CPUID.1:ECX bit 19.
  unsigned eax, ebx, ecx, edx;
  bool sse41 = __get_cpuid(1, &eax, &ebx, &ecx, &edx) && (ecx & (1u << 19));
  bool sha = __get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx) &&
             (ebx & (1u << 29));
  if (sha && sse41) return compress_shani;
#endif
  return compress_scalar;
}

CompressFn g_compress = pick_compress();

// Fallback two-lane form: two sequential single-lane compressions.  Used
// when SHA-NI is absent (the scalar routine's plain C already gives the
// compiler freedom to overlap iterations) or forced off for tests.
void compress2_seq(uint32_t sa[8], const uint8_t* ba, uint32_t sb[8],
                   const uint8_t* bb) {
  g_compress(sa, ba);
  g_compress(sb, bb);
}

Compress2Fn pick_compress2() {
#if P1_X86
  if (g_compress == compress_shani) return compress_shani2;
#endif
  return compress2_seq;
}

Compress2Fn g_compress2 = pick_compress2();

// --------------------------------------------------------------- helpers --

// One-shot SHA-256 of an arbitrary message.
void sha256(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  uint32_t st[8];
  std::memcpy(st, IV, sizeof(st));
  uint64_t full = len / 64;
  for (uint64_t i = 0; i < full; ++i) g_compress(st, data + 64 * i);
  uint8_t block[64];
  uint64_t rem = len - 64 * full;
  std::memcpy(block, data + 64 * full, rem);
  block[rem] = 0x80;
  std::memset(block + rem + 1, 0, 64 - rem - 1);
  if (rem + 1 > 56) {  // length field doesn't fit: one more block
    g_compress(st, block);
    std::memset(block, 0, 64);
  }
  uint64_t bits = len * 8;
  for (int i = 0; i < 8; ++i) block[56 + i] = uint8_t(bits >> (8 * (7 - i)));
  g_compress(st, block);
  for (int i = 0; i < 8; ++i) put_be32(out + 4 * i, st[i]);
}

// >= difficulty leading zero bits?  (digest < 2^(256-d), header.py:97-120)
inline bool leading_zero_bits_ge(const uint32_t digest_words[8], uint32_t d) {
  uint32_t full = d / 32, rem = d % 32;
  for (uint32_t i = 0; i < full; ++i)
    if (digest_words[i] != 0) return false;
  if (rem == 0) return true;
  return full < 8 && (digest_words[full] >> (32 - rem)) == 0;
}

// Same check over a big-endian 32-byte digest (the tiled verifiers keep
// digests in wire order so linkage is a flat memcmp).
inline bool leading_zero_bits_ge_bytes(const uint8_t digest[32], uint32_t d) {
  uint32_t full = d / 32, rem = d % 32;
  for (uint32_t i = 0; i < full; ++i)
    if (be32(digest + 4 * i) != 0) return false;
  if (rem == 0) return true;
  return full < 8 && (be32(digest + 4 * full) >> (32 - rem)) == 0;
}

}  // namespace

// ------------------------------------------------------------------- ABI --

extern "C" {

int p1_has_shani() {
#if P1_X86
  return g_compress != compress_scalar;
#else
  return 0;
#endif
}

// Test hook: force the portable scalar compression (enable=1) or restore
// the runtime-dispatched best path (enable=0), so the fallback is testable
// on SHA-NI hardware.
void p1_force_scalar(int enable) {
  g_compress = enable ? compress_scalar : pick_compress();
  g_compress2 = pick_compress2();  // keep the two-lane dispatch in step
}

void p1_sha256d(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  uint8_t first[32];
  sha256(data, len, first);
  sha256(first, 32, out);
}

// The three-compression SHA-256d of one 80-byte header, with the
// padding templates owned by a small reusable state so the two chain
// verifiers below cannot drift apart on the byte layout.
struct HeaderHasher {
  // 80-byte message templates: chunk 2 = bytes 64..80 + pad + bitlen
  // 640; second pass = 32-byte digest + pad + bitlen 256.
  uint8_t block2[64];
  uint8_t block3[64];
  HeaderHasher() {
    std::memset(block2, 0, sizeof(block2));
    block2[16] = 0x80;
    block2[62] = 0x02;
    block2[63] = 0x80;
    std::memset(block3, 0, sizeof(block3));
    block3[32] = 0x80;
    block3[62] = 0x01;
    block3[63] = 0x00;
  }
  void digest(const uint8_t* h, uint32_t st2[8]) {
    uint32_t st[8];
    std::memcpy(st, IV, sizeof(st));
    g_compress(st, h);
    std::memcpy(block2, h + 64, 16);
    g_compress(st, block2);
    for (int j = 0; j < 8; ++j) put_be32(block3 + 4 * j, st[j]);
    std::memcpy(st2, IV, 8 * sizeof(uint32_t));
    g_compress(st2, block3);
  }
};

// Two-lane form of HeaderHasher: digests two independent headers in
// lockstep through g_compress2 (interleaved SHA-NI when available), with
// per-lane padding templates.  Big-endian byte output so the verifiers
// can memcmp digests against prev_hash fields directly.
struct HeaderHasher2 {
  uint8_t block2a[64], block2b[64], block3a[64], block3b[64];
  HeaderHasher2() {
    for (uint8_t* b2 : {block2a, block2b}) {
      std::memset(b2, 0, 64);
      b2[16] = 0x80;
      b2[62] = 0x02;
      b2[63] = 0x80;
    }
    for (uint8_t* b3 : {block3a, block3b}) {
      std::memset(b3, 0, 64);
      b3[32] = 0x80;
      b3[62] = 0x01;
      b3[63] = 0x00;
    }
  }
  void digest2(const uint8_t* ha, const uint8_t* hb, uint8_t outa[32],
               uint8_t outb[32]) {
    uint32_t sa[8], sb[8];
    std::memcpy(sa, IV, sizeof(sa));
    std::memcpy(sb, IV, sizeof(sb));
    g_compress2(sa, ha, sb, hb);
    std::memcpy(block2a, ha + 64, 16);
    std::memcpy(block2b, hb + 64, 16);
    g_compress2(sa, block2a, sb, block2b);
    for (int j = 0; j < 8; ++j) {
      put_be32(block3a + 4 * j, sa[j]);
      put_be32(block3b + 4 * j, sb[j]);
    }
    std::memcpy(sa, IV, sizeof(sa));
    std::memcpy(sb, IV, sizeof(sb));
    g_compress2(sa, block3a, sb, block3b);
    for (int j = 0; j < 8; ++j) {
      put_be32(outa + 4 * j, sa[j]);
      put_be32(outb + 4 * j, sb[j]);
    }
  }
};

// Digest one tile of headers into `out` (32 B/header, big-endian),
// pairwise through the two-lane hasher.  The tile shape keeps the
// verifiers' early-exit granularity (a hostile prefix costs at most one
// tile of extra hashing), bounds scratch to a constant, and keeps the
// just-computed digests L1-warm for the check pass that follows.
constexpr uint64_t VERIFY_TILE = 512;

void digest_tile(const uint8_t* headers, uint64_t count, uint8_t* out) {
  HeaderHasher2 h2;
  uint64_t i = 0;
  for (; i + 2 <= count; i += 2)
    h2.digest2(headers + 80 * i, headers + 80 * (i + 1), out + 32 * i,
               out + 32 * (i + 1));
  if (i < count) {
    HeaderHasher h1;
    uint32_t st2[8];
    h1.digest(headers + 80 * i, st2);
    for (int j = 0; j < 8; ++j) put_be32(out + 32 * i + 4 * j, st2[j]);
  }
}

// Verify a header chain laid out as n contiguous 80-byte headers
// (layout: version[0..4) prev_hash[4..36) merkle[36..68) timestamp[68..72)
// difficulty[72..76) nonce[76..80), all big-endian — core/header.py's
// _PACK).  Per header: SHA-256d meets >= `difficulty` leading zero bits
// (header 0 exempt when genesis_exempt — it anchors by identity), the
// difficulty field equals `difficulty`, and prev_hash equals the previous
// header's digest (header 0 links to 32 zero bytes).  Exactly
// chain/replay.py::replay_host's rules — this is its native engine
// (benchmark config 3).  Structured as digest-tile-then-check so the
// independent per-header hashes run two-lane (compress_shani2) while the
// serial linkage walk stays a flat memcmp over the tile's digests.
// Returns the first invalid index, or -1.
long long p1_verify_chain(const uint8_t* headers, uint64_t n,
                          uint32_t difficulty, int genesis_exempt) {
  uint8_t dig[VERIFY_TILE * 32];
  uint8_t prev[32];
  std::memset(prev, 0, sizeof(prev));
  for (uint64_t base = 0; base < n; base += VERIFY_TILE) {
    const uint64_t count = (n - base < VERIFY_TILE) ? (n - base) : VERIFY_TILE;
    digest_tile(headers + 80 * base, count, dig);
    for (uint64_t k = 0; k < count; ++k) {
      const uint64_t i = base + k;
      const uint8_t* h = headers + 80 * i;
      const uint8_t* d = dig + 32 * k;
      bool pow_ok = (genesis_exempt && i == 0) ||
                    leading_zero_bits_ge_bytes(d, difficulty);
      bool diff_ok = be32(h + 72) == difficulty;
      bool link_ok = std::memcmp(h + 4, prev, 32) == 0;
      if (!(pow_ok && diff_ok && link_ok)) return (long long)i;
      std::memcpy(prev, d, 32);
    }
  }
  return -1;
}

// RetargetRule.adjusted (core/retarget.py), bit-for-bit: one bit harder
// per halving of the expected span, one easier per doubling, clamped to
// max_adjust and 1..255.  Integer-only, exactly the Python rule.
static uint32_t rt_adjusted(uint32_t parent_d, long long span,
                            uint32_t window, uint32_t spacing,
                            uint32_t max_adjust) {
  const long long expected = (long long)spacing * (long long)(window - 1);
  if (span < 1) span = 1;
  int adj = 0;
  while (adj < (int)max_adjust && span * (2LL << adj) <= expected) adj++;
  if (adj == 0) {
    while (adj > -(int)max_adjust && span >= (2LL << (-adj)) * expected)
      adj--;
  }
  long long nd = (long long)parent_d + adj;
  if (nd < 1) nd = 1;
  if (nd > 255) nd = 255;
  return (uint32_t)nd;
}

// Retargeting variant of p1_verify_chain: same layout, but the required
// difficulty is the CONTEXTUAL schedule (a pure function of the ancestor
// headers — chain/chain.py), and the timestamp rules apply: strictly
// increasing, with the forward-dating cap of max_step*spacing seconds per
// block from height 2 on (height 1 is the bootstrap clock anchor —
// core/retarget.py).  Header 0 is the genesis record: validated by
// identity upstream (the Python caller checks the genesis hash), so PoW
// is waived and its difficulty field seeds the schedule.  Mirrors
// chain/replay.py::replay_host(retarget=...) rule-for-rule — the parity
// tests corrupt chains at boundaries and compare first-invalid indices.
// Returns the first invalid index, or -1.
long long p1_verify_chain_retarget(const uint8_t* headers, uint64_t n,
                                   uint32_t window, uint32_t spacing,
                                   uint32_t max_adjust, uint32_t max_step) {
  if (window < 2 || spacing < 1) return 0;
  // Ring of the last `window` timestamps: at a boundary i the span is
  // ts[i-1] - ts[i-window], and slot i % window still holds ts[i-window].
  std::vector<uint32_t> ring((size_t)window, 0);
  uint8_t dig[VERIFY_TILE * 32];
  uint8_t prev[32];
  std::memset(prev, 0, sizeof(prev));
  uint32_t prev_ts = 0, prev_d = 0;
  for (uint64_t i = 0; i < n; ++i) {
    const uint64_t k = i % VERIFY_TILE;
    if (k == 0) {
      const uint64_t count =
          (n - i < VERIFY_TILE) ? (n - i) : VERIFY_TILE;
      digest_tile(headers + 80 * i, count, dig);  // two-lane, see above
    }
    const uint8_t* h = headers + 80 * i;
    const uint8_t* d32 = dig + 32 * k;

    const uint32_t ts = be32(h + 68);
    const uint32_t d = be32(h + 72);
    uint32_t expected;
    if (i == 0) {
      expected = d;  // genesis seeds the schedule (identity-checked)
    } else if (i % window != 0) {
      expected = prev_d;
    } else {
      const long long span =
          (long long)prev_ts - (long long)ring[i % window];
      expected = rt_adjusted(prev_d, span, window, spacing, max_adjust);
    }
    const bool pow_ok =
        (i == 0) || leading_zero_bits_ge_bytes(d32, expected);
    const bool diff_ok = d == expected;
    const bool link_ok = std::memcmp(h + 4, prev, 32) == 0;
    const bool ts_ok =
        (i == 0) ||
        ((long long)ts > (long long)prev_ts &&
         (i == 1 || (long long)ts - (long long)prev_ts <=
                        (long long)max_step * (long long)spacing));
    if (!(pow_ok && diff_ok && link_ok && ts_ok)) return (long long)i;
    ring[i % window] = ts;
    prev_ts = ts;
    prev_d = d;
    std::memcpy(prev, d32, 32);
  }
  return -1;
}

// Earliest nonce in [nonce_start, nonce_start+count) whose header SHA-256d
// has >= difficulty leading zero bits, or -1.  prefix is the fixed 76-byte
// header head; the first 64 bytes compress once (midstate).
long long p1_search(const uint8_t prefix[76], uint32_t nonce_start,
                    uint64_t count, uint32_t difficulty) {
  uint32_t midstate[8];
  std::memcpy(midstate, IV, sizeof(midstate));
  g_compress(midstate, prefix);

  // Chunk 2 template: prefix bytes 64..76, nonce at 12..16, pad, bitlen 640.
  uint8_t block2[64];
  std::memset(block2, 0, sizeof(block2));
  std::memcpy(block2, prefix + 64, 12);
  block2[16] = 0x80;
  block2[62] = 0x02;  // 640 = 0x0280 big-endian in bytes 56..64
  block2[63] = 0x80;

  // Second-pass template: 32-byte digest, pad, bitlen 256.
  uint8_t block3[64];
  std::memset(block3, 0, sizeof(block3));
  block3[32] = 0x80;
  block3[62] = 0x01;  // 256 = 0x0100
  block3[63] = 0x00;

  const uint64_t end = uint64_t(nonce_start) + count;
  for (uint64_t nonce = nonce_start; nonce < end; ++nonce) {
    put_be32(block2 + 12, uint32_t(nonce));
    uint32_t st[8];
    std::memcpy(st, midstate, sizeof(st));
    g_compress(st, block2);
    for (int i = 0; i < 8; ++i) put_be32(block3 + 4 * i, st[i]);
    uint32_t st2[8];
    std::memcpy(st2, IV, sizeof(st2));
    g_compress(st2, block3);
    if (leading_zero_bits_ge(st2, difficulty)) return (long long)nonce;
  }
  return -1;
}

}  // extern "C"
