// Native Ed25519 batch verifier — the crypto-engine half of the native
// core (ROADMAP item 1 route (a): "grow native/sha256d.cpp into a real
// native crypto engine").
//
// Scope and division of labor: this file is the FIELD/GROUP engine only.
// Everything that is already C-speed in CPython stays in the Python seam
// (p1_tpu/core/_ed25519_native.py): SHA-512 (hashlib), the mod-q scalar
// bignum work (CPython long arithmetic), length/canonicality checks, and
// the per-batch random coefficients (secrets).  What crosses the ctypes
// boundary is pure curve arithmetic — the part that costs ~1.4 ms/sig in
// pure Python and ~40 µs here:
//
//   p1_ed25519_verify(pub, R, s, k)  - ONE cofactorless serial check
//                                      [s]B == R + [k]A, decompress rules
//                                      bit-identical to core/_ed25519.py
//   p1_ed25519_batch(...)            - subgroup-gate every A (deduped by
//                                      the caller) and every R exactly
//                                      ([q]·P == identity), then evaluate
//                                      the random-linear-combination MSM
//                                      by Pippenger's bucket method
//   p1_ed25519_in_subgroup(enc)      - the exact gate alone (test hook)
//   p1_ed25519_impl()                - which arithmetic runs (telemetry)
//
// The SEMANTICS contract is core/_ed25519.py's, restated: batch
// acceptance must imply serial (cofactorless) acceptance of every
// triple, which requires the EXACT prime-subgroup gate [q]·P == identity
// on every point — no probabilistic shortcut exists (the torsion group
// is Z/8, far too small for random-linear-combination soundness).  The
// serial entry point is deliberately UNGATED and reduces k mod q before
// multiplying, exactly like the Python serial path, so torsion-crafted
// signatures the serial equation tolerates get the same ACCEPT here —
// one validity rule on every node, whichever backend it runs
// (tests/test_native_ed25519.py pins parity input-for-input).
//
// Arithmetic: radix-2^51 field elements (5 × uint64 limbs) with
// unsigned __int128 products — portable to any 64-bit target, no
// CPUID dispatch needed (unlike the SHA-NI half of this library the
// hot loop is multiply-bound, which every target's compiler already
// schedules well).  Formulas are the extended-coordinate add/double of
// core/_ed25519.py translated limb-wise, so parity testing against the
// Python oracle covers every path.

#include <cstdint>
#include <cstring>
#include <vector>

#if !defined(__SIZEOF_INT128__)
#error "p1 native ed25519 requires a 64-bit target with __int128"
#endif

namespace {

typedef unsigned __int128 u128;

// ------------------------------------------------------------ fe25519 --
// Limbs < 2^52 when "reduced"; add/sub outputs may grow to < 2^55 and
// feed straight into mul/sq (products stay far below 2^127) — the point
// formulas below never chain more than two additive ops into a product.

struct fe {
  uint64_t v[5];
};

constexpr uint64_t MASK51 = (uint64_t(1) << 51) - 1;

inline fe fe_zero() { return {{0, 0, 0, 0, 0}}; }
inline fe fe_one() { return {{1, 0, 0, 0, 0}}; }

inline uint64_t load64(const uint8_t* p) {
  uint64_t r;
  std::memcpy(&r, p, 8);
  return r;  // little-endian hosts only (x86-64/aarch64)
}

inline void store64(uint8_t* p, uint64_t v) { std::memcpy(p, &v, 8); }

// 32 LE bytes (top bit ignored by the caller's masking) -> 5 limbs.
inline fe fe_frombytes(const uint8_t s[32]) {
  fe r;
  r.v[0] = load64(s) & MASK51;
  r.v[1] = (load64(s + 6) >> 3) & MASK51;
  r.v[2] = (load64(s + 12) >> 6) & MASK51;
  r.v[3] = (load64(s + 19) >> 1) & MASK51;
  r.v[4] = (load64(s + 24) >> 12) & MASK51;
  return r;
}

inline void fe_carry(fe& a) {
  for (int pass = 0; pass < 2; ++pass) {
    uint64_t c;
    for (int i = 0; i < 4; ++i) {
      c = a.v[i] >> 51;
      a.v[i] &= MASK51;
      a.v[i + 1] += c;
    }
    c = a.v[4] >> 51;
    a.v[4] &= MASK51;
    a.v[0] += 19 * c;
  }
}

// Every fe in the system keeps limbs < 2^52 (fe_mul's carry chain
// guarantees it for products; add/sub re-carry below) — two dozen
// shift/mask ops per op buys freedom from magnitude bookkeeping across
// the point formulas, and the cost is noise next to the 25-product
// multiplications that dominate.

inline fe fe_add(const fe& a, const fe& b) {
  fe r;
  for (int i = 0; i < 5; ++i) r.v[i] = a.v[i] + b.v[i];
  fe_carry(r);
  return r;
}

// a - b without underflow: add 4p (limb-shaped, > any reduced limb)
// first.  Inputs < 2^52 by the invariant above; output re-carried.
inline fe fe_sub(const fe& a, const fe& b) {
  static const uint64_t P4[5] = {
      (MASK51 + 1 - 19) << 2, MASK51 << 2, MASK51 << 2, MASK51 << 2,
      MASK51 << 2};
  fe r;
  for (int i = 0; i < 5; ++i) r.v[i] = a.v[i] + P4[i] - b.v[i];
  fe_carry(r);
  return r;
}

inline fe fe_mul(const fe& a, const fe& b) {
  const uint64_t a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3],
                 a4 = a.v[4];
  const uint64_t b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3],
                 b4 = b.v[4];
  const uint64_t b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19,
                 b4_19 = b4 * 19;
  u128 t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
            (u128)a3 * b2_19 + (u128)a4 * b1_19;
  u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
            (u128)a3 * b3_19 + (u128)a4 * b2_19;
  u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
            (u128)a3 * b4_19 + (u128)a4 * b3_19;
  u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
            (u128)a3 * b0 + (u128)a4 * b4_19;
  u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
            (u128)a3 * b1 + (u128)a4 * b0;
  fe r;
  uint64_t c;
  r.v[0] = (uint64_t)t0 & MASK51;
  t1 += (uint64_t)(t0 >> 51);
  r.v[1] = (uint64_t)t1 & MASK51;
  t2 += (uint64_t)(t1 >> 51);
  r.v[2] = (uint64_t)t2 & MASK51;
  t3 += (uint64_t)(t2 >> 51);
  r.v[3] = (uint64_t)t3 & MASK51;
  t4 += (uint64_t)(t3 >> 51);
  r.v[4] = (uint64_t)t4 & MASK51;
  c = (uint64_t)(t4 >> 51);
  r.v[0] += 19 * c;
  c = r.v[0] >> 51;
  r.v[0] &= MASK51;
  r.v[1] += c;
  return r;
}

inline fe fe_sq(const fe& a) { return fe_mul(a, a); }

// Fully reduce to the canonical 32-byte little-endian representative.
inline void fe_tobytes(uint8_t out[32], const fe& a) {
  fe t = a;
  fe_carry(t);
  fe_carry(t);
  // t < 2^255 + small now; one more conditional wrap for t4 overflow
  uint64_t c = t.v[4] >> 51;
  t.v[4] &= MASK51;
  t.v[0] += 19 * c;
  for (int i = 0; i < 4; ++i) {
    c = t.v[i] >> 51;
    t.v[i] &= MASK51;
    t.v[i + 1] += c;
  }
  // conditional subtract p: q = 1 iff t >= p  (t + 19 carries past 2^255)
  uint64_t q = (t.v[0] + 19) >> 51;
  q = (t.v[1] + q) >> 51;
  q = (t.v[2] + q) >> 51;
  q = (t.v[3] + q) >> 51;
  q = (t.v[4] + q) >> 51;
  t.v[0] += 19 * q;
  for (int i = 0; i < 4; ++i) {
    c = t.v[i] >> 51;
    t.v[i] &= MASK51;
    t.v[i + 1] += c;
  }
  t.v[4] &= MASK51;
  uint8_t buf[40] = {0};
  store64(buf + 0, t.v[0] | (t.v[1] << 51));
  store64(buf + 8, (t.v[1] >> 13) | (t.v[2] << 38));
  store64(buf + 16, (t.v[2] >> 26) | (t.v[3] << 25));
  store64(buf + 24, (t.v[3] >> 39) | (t.v[4] << 12));
  std::memcpy(out, buf, 32);
}

inline bool fe_eq(const fe& a, const fe& b) {
  uint8_t ba[32], bb[32];
  fe_tobytes(ba, a);
  fe_tobytes(bb, b);
  return std::memcmp(ba, bb, 32) == 0;
}

inline bool fe_is_zero(const fe& a) {
  uint8_t b[32];
  fe_tobytes(b, a);
  for (int i = 0; i < 32; ++i)
    if (b[i]) return false;
  return true;
}

// Generic square-and-multiply over a 255-bit little-endian exponent —
// used a handful of times per signature (decompression) and at init,
// where a hand-tuned addition chain would buy microseconds.
fe fe_pow(const fe& base, const uint8_t exp[32]) {
  fe r = fe_one();
  bool started = false;
  for (int byte = 31; byte >= 0; --byte) {
    for (int bit = 7; bit >= 0; --bit) {
      if (started) r = fe_sq(r);
      if ((exp[byte] >> bit) & 1) {
        if (started)
          r = fe_mul(r, base);
        else {
          r = base;
          started = true;
        }
      }
    }
  }
  return started ? r : fe_one();
}

// ---------------------------------------------------------- ge25519 ----
// Extended homogeneous coordinates (X, Y, Z, T), XY = ZT — the exact
// formulas of core/_ed25519.py::_pt_add/_pt_double, limb-wise.

struct ge {
  fe x, y, z, t;
};

struct Consts {
  fe d;        // edwards d = -121665/121666
  fe d2;       // 2d (hoisted out of every addition)
  fe sqrt_m1;  // sqrt(-1), for decompression
  ge B;        // base point
  uint8_t exp_pm5d8[32];  // (p-5)/8
};

inline ge ge_identity() { return {fe_zero(), fe_one(), fe_one(), fe_zero()}; }

const Consts& consts();  // fwd

inline ge ge_add(const ge& p, const ge& q) {
  fe aa = fe_mul(fe_sub(p.y, p.x), fe_sub(q.y, q.x));
  fe bb = fe_mul(fe_add(p.y, p.x), fe_add(q.y, q.x));
  fe cc = fe_mul(fe_mul(p.t, q.t), consts().d2);
  fe zz = fe_mul(p.z, q.z);
  fe dd = fe_add(zz, zz);
  fe e = fe_sub(bb, aa);
  fe f = fe_sub(dd, cc);
  fe g = fe_add(dd, cc);
  fe h = fe_add(bb, aa);
  return {fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

inline ge ge_double(const ge& p) {
  fe aa = fe_sq(p.x);
  fe bb = fe_sq(p.y);
  fe zz = fe_sq(p.z);
  fe cc = fe_add(zz, zz);
  fe h = fe_add(aa, bb);
  fe xy = fe_add(p.x, p.y);
  fe e = fe_sub(h, fe_sq(xy));
  fe g = fe_sub(aa, bb);
  fe f = fe_add(cc, g);
  return {fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

// Projective equality by cross-multiplication (no inversions) —
// core/_ed25519.py::_pt_equal.
inline bool ge_eq(const ge& a, const ge& b) {
  return fe_eq(fe_mul(a.x, b.z), fe_mul(b.x, a.z)) &&
         fe_eq(fe_mul(a.y, b.z), fe_mul(b.y, a.z));
}

inline bool ge_is_identity(const ge& a) {
  return fe_is_zero(a.x) && fe_eq(a.y, a.z);
}

// 4-bit fixed-window scalar multiplication, most-significant window
// first, over a 256-bit little-endian scalar.  Variable-time: every
// input here is public (verification, not signing).
ge ge_scalarmult(const uint8_t scalar[32], const ge& p) {
  ge table[16];
  table[0] = ge_identity();
  table[1] = p;
  for (int i = 2; i < 16; ++i) table[i] = ge_add(table[i - 1], p);
  ge acc = ge_identity();
  bool started = false;
  for (int i = 63; i >= 0; --i) {
    unsigned w = (scalar[i >> 1] >> ((i & 1) * 4)) & 15;
    if (started) {
      acc = ge_double(ge_double(ge_double(ge_double(acc))));
    }
    if (w) {
      acc = started ? ge_add(acc, table[w]) : table[w];
      started = true;
    } else if (!started) {
      continue;  // skip leading zero windows entirely
    }
  }
  return acc;
}

// Point decompression, rule-for-rule core/_ed25519.py::_pt_decompress /
// _recover_x (the serial-parity contract lives or dies here):
// reject y >= p; u = y^2-1, v = d*y^2+1; u == 0 -> reject iff sign else
// x = 0; candidate x = u*v^3*(u*v^7)^((p-5)/8); accept x or x*sqrt(-1)
// by checking v*x^2 against ±u; reject x == 0 with sign set; negate to
// match the sign bit.
bool ge_decompress(ge& out, const uint8_t enc[32]) {
  uint8_t ybytes[32];
  std::memcpy(ybytes, enc, 32);
  const unsigned sign = ybytes[31] >> 7;
  ybytes[31] &= 0x7f;
  // y must be canonical (< p): compare little-endian against p's bytes.
  static const uint8_t PB[32] = {
      0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  for (int i = 31; i >= 0; --i) {
    if (ybytes[i] < PB[i]) break;
    if (ybytes[i] > PB[i] || i == 0) return false;  // y >= p
  }
  const fe y = fe_frombytes(ybytes);
  const fe y2 = fe_sq(y);
  const fe u = fe_sub(y2, fe_one());
  const fe v = fe_add(fe_mul(consts().d, y2), fe_one());
  fe x;
  if (fe_is_zero(u)) {
    if (sign) return false;
    x = fe_zero();
  } else {
    const fe v3 = fe_mul(fe_sq(v), v);
    const fe uv3 = fe_mul(u, v3);
    const fe uv7 = fe_mul(uv3, fe_mul(v3, v));
    x = fe_mul(uv3, fe_pow(uv7, consts().exp_pm5d8));
    const fe vx2 = fe_mul(v, fe_sq(x));
    if (!fe_eq(vx2, u)) {
      if (!fe_eq(vx2, fe_sub(fe_zero(), u))) return false;
      x = fe_mul(x, consts().sqrt_m1);
    }
    uint8_t xb[32];
    fe_tobytes(xb, x);
    const bool x_zero = fe_is_zero(x);
    if (x_zero && sign) return false;
    if ((xb[0] & 1) != sign) x = fe_sub(fe_zero(), x);
  }
  out.x = x;
  out.y = y;
  out.z = fe_one();
  out.t = fe_mul(x, y);
  return true;
}

const Consts& consts() {
  static const Consts C = [] {
    Consts c;
    // d = -121665/121666: one generic inversion at first use beats
    // transcribing a 255-bit constant that could silently rot.
    uint8_t exp_pm2[32];
    std::memset(exp_pm2, 0xff, 32);
    exp_pm2[0] = 0xeb;
    exp_pm2[31] = 0x7f;
    std::memset(c.exp_pm5d8, 0xff, 32);
    c.exp_pm5d8[0] = 0xfd;
    c.exp_pm5d8[31] = 0x0f;
    fe n121665 = {{121665, 0, 0, 0, 0}};
    fe n121666 = {{121666, 0, 0, 0, 0}};
    c.d = fe_mul(fe_sub(fe_zero(), n121665), fe_pow(n121666, exp_pm2));
    c.d2 = fe_add(c.d, c.d);
    // sqrt(-1) = 2^((p-1)/4)
    uint8_t exp_pm1d4[32];
    std::memset(exp_pm1d4, 0xff, 32);
    exp_pm1d4[0] = 0xfb;
    exp_pm1d4[31] = 0x1f;
    fe two = {{2, 0, 0, 0, 0}};
    c.sqrt_m1 = fe_pow(two, exp_pm1d4);
    // base point from its standard compressed encoding (y = 4/5).
    uint8_t b_enc[32];
    std::memset(b_enc, 0x66, 32);
    b_enc[0] = 0x58;
    ge b;
    // consts() is re-entered by ge_decompress via c.d — but d and
    // sqrt_m1 are already set above and B is only READ after init, so
    // decompress directly with the locals instead of recursing.
    // (Simplest correct form: inline the same math through ge_decompress
    // once C is published would recurse; so build B by scalar-free
    // decompression using the fields already in `c`.)
    const unsigned sign = b_enc[31] >> 7;
    uint8_t yb[32];
    std::memcpy(yb, b_enc, 32);
    yb[31] &= 0x7f;
    const fe y = fe_frombytes(yb);
    const fe y2 = fe_sq(y);
    const fe u = fe_sub(y2, fe_one());
    const fe v = fe_add(fe_mul(c.d, y2), fe_one());
    const fe v3 = fe_mul(fe_sq(v), v);
    const fe uv7 = fe_mul(fe_mul(u, v3), fe_mul(v3, v));
    fe x = fe_mul(fe_mul(u, v3), fe_pow(uv7, c.exp_pm5d8));
    const fe vx2 = fe_mul(v, fe_sq(x));
    if (!fe_eq(vx2, u)) x = fe_mul(x, c.sqrt_m1);
    uint8_t xb[32];
    fe_tobytes(xb, x);
    if ((xb[0] & 1) != sign) x = fe_sub(fe_zero(), x);
    b.x = x;
    b.y = y;
    b.z = fe_one();
    b.t = fe_mul(x, y);
    c.B = b;
    return c;
  }();
  return C;
}

//: q (the prime group order), little-endian — pinned against
//: core/_ed25519.py::_Q by tests/test_native_ed25519.py.
const uint8_t Q_BYTES[32] = {
    0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58, 0xd6, 0x9c, 0xf7,
    0xa2, 0xde, 0xf9, 0xde, 0x14, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};

// Exact prime-subgroup membership: [q]·P == identity.  The torsion
// group is Z/8 — far too small for any probabilistic shortcut, so the
// gate is a full scalar multiplication by q per point (the dominant
// per-signature batch cost, same trade core/_ed25519.py documents).
inline bool in_prime_subgroup(const ge& p) {
  return ge_is_identity(ge_scalarmult(Q_BYTES, p));
}

// --------------------------------------------------------- Pippenger ---

struct Pair {
  uint64_t s[4];  // 256-bit scalar, little-endian words
  ge p;
};

inline unsigned scalar_bits(const uint64_t s[4]) {
  for (int w = 3; w >= 0; --w)
    if (s[w]) return 64 * w + (64 - __builtin_clzll(s[w]));
  return 0;
}

inline unsigned digit_at(const uint64_t s[4], unsigned base, unsigned c) {
  const unsigned word = base >> 6, off = base & 63;
  uint64_t d = s[word] >> off;
  if (off + c > 64 && word + 1 < 4) d |= s[word + 1] << (64 - off);
  return (unsigned)(d & ((uint64_t(1) << c) - 1));
}

// Σ scalar·point by Pippenger's bucket method — the same window-size
// model and running-sum aggregation as core/_ed25519.py::_msm.
ge msm(const std::vector<Pair>& pairs) {
  unsigned maxbits = 0;
  for (const Pair& pr : pairs) {
    unsigned b = scalar_bits(pr.s);
    if (b > maxbits) maxbits = b;
  }
  if (maxbits == 0) return ge_identity();
  const uint64_t n = pairs.size();
  unsigned c = 2;
  u128 best = ~(u128)0;
  for (unsigned w = 2; w < 16; ++w) {
    const u128 cost =
        (u128)((maxbits + w - 1) / w) * (n + ((uint64_t)2 << w));
    if (cost < best) {
      best = cost;
      c = w;
    }
  }
  const unsigned nbuckets = 1u << c;
  std::vector<ge> buckets(nbuckets);
  std::vector<uint8_t> present(nbuckets);
  ge result = ge_identity();
  bool result_set = false;
  for (int shift = (int)((maxbits + c - 1) / c) - 1; shift >= 0; --shift) {
    if (result_set)
      for (unsigned i = 0; i < c; ++i) result = ge_double(result);
    std::memset(present.data(), 0, nbuckets);
    const unsigned base = (unsigned)shift * c;
    for (const Pair& pr : pairs) {
      const unsigned idx = digit_at(pr.s, base, c);
      if (!idx) continue;
      buckets[idx] = present[idx] ? ge_add(buckets[idx], pr.p) : pr.p;
      present[idx] = 1;
    }
    ge running, acc;
    bool have_running = false, have_acc = false;
    for (unsigned idx = nbuckets - 1; idx >= 1; --idx) {
      if (present[idx]) {
        running = have_running ? ge_add(running, buckets[idx]) : buckets[idx];
        have_running = true;
      }
      if (have_running) {
        acc = have_acc ? ge_add(acc, running) : running;
        have_acc = true;
      }
    }
    if (have_acc) {
      result = result_set ? ge_add(result, acc) : acc;
      result_set = true;
    }
  }
  return result;
}

inline void scalar_words(uint64_t out[4], const uint8_t s[32]) {
  for (int w = 0; w < 4; ++w) out[w] = load64(s + 8 * w);
}

}  // namespace

// ------------------------------------------------------------------ ABI --

extern "C" {

// Which arithmetic this build runs (backend telemetry; the SHA half of
// the library reports its own SHA-NI dispatch separately).
const char* p1_ed25519_impl() { return "u128-radix51"; }

// Exact subgroup gate on one compressed point: 1 in the prime-order
// subgroup, 0 torsioned, -1 undecodable.
int p1_ed25519_in_subgroup(const uint8_t enc[32]) {
  ge p;
  if (!ge_decompress(p, enc)) return -1;
  return in_prime_subgroup(p) ? 1 : 0;
}

// ONE serial cofactorless verification: [s]B == R + [k]A.  `s` and `k`
// are 32-byte little-endian scalars the caller already range-checked
// (s < q) / reduced (k mod q) — exactly what core/_ed25519.py::verify
// computes before its point math, so verdicts are bit-identical,
// torsion crafts included.  Deliberately NO subgroup gate here: the
// serial rule tolerates torsion that cancels, and this entry point IS
// the serial rule.
int p1_ed25519_verify(const uint8_t pub[32], const uint8_t r_enc[32],
                      const uint8_t s[32], const uint8_t k[32]) {
  ge a, r;
  if (!ge_decompress(a, pub)) return 0;
  if (!ge_decompress(r, r_enc)) return 0;
  const ge sb = ge_scalarmult(s, consts().B);
  const ge ka = ge_scalarmult(k, a);
  return ge_eq(sb, ge_add(r, ka)) ? 1 : 0;
}

// Batched verification core: gate + random-linear-combination MSM.
//
//   pubs     m unique compressed public keys (32 B each; caller dedups)
//   pub_idx  n uint32 indices into pubs, one per signature
//   r_encs   n compressed R points (32 B each)
//   zr       n 32-byte LE scalars for the R terms   (z_i)
//   za       n 32-byte LE scalars for the A terms   (z_i·k_i mod q)
//   sb       one 32-byte LE scalar for the B term   (q − Σ z_i·s_i mod q)
//
// Accepts (returns 1) iff every pubkey and every R decompresses into
// the PRIME-ORDER subgroup (exact [q]·P == identity — checked once per
// unique pubkey, per signature for R) and
//   Σ zr_i·R_i + Σ za_i·A_i + sb·B == identity.
// With every point proven torsion-free the mod-q scalar reductions the
// caller performed are exact and each term of the sum is the serial
// equation itself — batch acceptance implies serial acceptance up to
// the 2^-128 soundness of the caller's random coefficients.  0 is NOT
// a serial verdict (the gate also rejects serial-tolerated torsion
// crafts); the Python seam settles failures via keys.first_invalid.
int p1_ed25519_batch(const uint8_t* pubs, uint64_t m,
                     const uint32_t* pub_idx, const uint8_t* r_encs,
                     const uint8_t* zr, const uint8_t* za,
                     const uint8_t* sb, uint64_t n) {
  std::vector<ge> apts(m);
  for (uint64_t i = 0; i < m; ++i) {
    if (!ge_decompress(apts[i], pubs + 32 * i)) return 0;
    if (!in_prime_subgroup(apts[i])) return 0;
  }
  std::vector<Pair> pairs;
  pairs.reserve(2 * n + 1);
  for (uint64_t i = 0; i < n; ++i) {
    ge r;
    if (!ge_decompress(r, r_encs + 32 * i)) return 0;
    if (!in_prime_subgroup(r)) return 0;
    Pair pr;
    scalar_words(pr.s, zr + 32 * i);
    pr.p = r;
    if (scalar_bits(pr.s)) pairs.push_back(pr);
    Pair pa;
    scalar_words(pa.s, za + 32 * i);
    if (pub_idx[i] >= m) return 0;
    pa.p = apts[pub_idx[i]];
    if (scalar_bits(pa.s)) pairs.push_back(pa);
  }
  Pair pb;
  scalar_words(pb.s, sb);
  pb.p = consts().B;
  if (scalar_bits(pb.s)) pairs.push_back(pb);
  if (pairs.empty()) return 1;
  return ge_is_identity(msm(pairs)) ? 1 : 0;
}

}  // extern "C"
