"""p1-tpu: a TPU-native proof-of-work blockchain framework.

A ground-up rebuild of the capabilities of the reference project `qzwlecr/p1`
(see SURVEY.md — the reference checkout was unavailable, so parity is built
against the driver-recorded capability model in /root/repo/BASELINE.json):

- ``p1_tpu.core``    — block/header/transaction types, deterministic
  serialization, difficulty/target math, genesis, Ed25519 account keys
  (account id = key fingerprint; chain-bound signed transfers).
- ``p1_tpu.hashx``   — the ``HashBackend`` plugin registry (BASELINE.json:5)
  with CPU (hashlib), C++ ``native`` (SHA-NI when available, built lazily
  from p1_tpu/native/), NumPy-oracle, JAX/XLA, Pallas-TPU (``tpu``) and
  multi-chip ``sharded`` backends.
- ``p1_tpu.miner``   — ``Miner.search_nonce()`` (BASELINE.json:5): the nonce
  search as batched device steps; multi-chip sharding with a pmin first-hit
  reduction over a ``jax.sharding.Mesh``.
- ``p1_tpu.chain``   — stateless + contextual validation (signatures,
  subsidy, overdraw rejection, strict account nonces via the incremental
  tip ledger), longest-chain fork choice with reorg and invalid-branch
  demotion, fsync-durable persistence (checkpoint/resume), header-chain
  replay (host / C++ native / one-dispatch device engines).
- ``p1_tpu.mempool`` — pending-transaction pool (per-(sender, seq) slots,
  replace-by-fee, confirmed-slot replay window, balance/nonce-aware
  admission + gap-free block selection, sorted sync index).
- ``p1_tpu.node``    — asyncio TCP p2p gossip node (versioned protocol;
  blocks + txs, locator block sync, paged mempool sync, account queries,
  propagation-delay metrics) + thin wallet clients (send_tx, get_account).
- ``p1_tpu.parallel`` — multi-host pod mining: one ``jax.distributed``
  mesh across processes/hosts, lockstep searches, one miner on the
  gossip network.
"""

__version__ = "0.1.0"
